"""Compiled-HLO audits of the hierarchical quantized collectives.

Tier-1 (NOT slow): these compile small shard_map programs / a tiny
engine step — seconds, not minutes — yet pin the exact properties the
hardware cannot be reached to measure:

- qgZ two-hop gradient allreduce per-rank wire is O(n): byte-identical
  at W=4 and W=8, and <= 0.6x the dense bf16 ring allreduce at W=8 for
  a >= 1M-element gradient (ISSUE 2 acceptance).
- the legacy all_gather exchange exceeds the dense bf16 ring at W >= 4
  — the regression that motivated the rewrite.
- hierarchical mode keeps the bandwidth-heavy hops on the intra
  sub-axis; only the reduced 1/W_intra chunk crosses the inter axis.
- the production micro step routes gradients through the two-hop shape
  (s8 all_to_all + chunk gather, no full-tensor s8 all_gather).
- qwZ: the ZeRO param all-gather moves int8 elements; with hpZ the s8
  weight movement crosses the inter axis only.

Byte accounting on int8 payloads IS backend-invariant (the CPU
backend's FloatNormalization touches only floats), which is why these
audits count bytes where test_hlo_collectives.py counts elements.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.quantized_collectives import (
    ALGO_ALLGATHER, ALGO_TWOHOP, hierarchical_quantized_allreduce_mean,
    quantized_allreduce_mean, wire_bytes)
from deepspeed_tpu.utils.hlo_audit import (
    collect_collectives_full, dense_allreduce_ring_bytes, wire_bytes_of)

N = 1 << 20          # >= 1M-element gradient (acceptance criterion)


def _collective_hlo(n, world, algo):
    mesh = build_mesh({"data": world})

    def inner(x):
        return quantized_allreduce_mean(x[0], "data", algo=algo,
                                        world_size=world)

    g = jax.ShapeDtypeStruct((world, n), jnp.float32)
    fn = jax.jit(jax.shard_map(inner, mesh=mesh, in_specs=(P("data"),),
                               out_specs=P(), check_vma=False))
    return fn.lower(g).compile().as_text()


def test_twohop_wire_is_o_n_and_beats_dense_bf16():
    """Two-hop per-rank wire bytes are independent of W and <= 0.6x the
    dense bf16 ring at W=8 for a 1M-element gradient."""
    measured = {}
    for W in (4, 8):
        colls = collect_collectives_full(_collective_hlo(N, W, ALGO_TWOHOP))
        assert colls, "two-hop program compiled without collectives?"
        measured[W] = wire_bytes_of(colls)
        # no full-tensor quantized all_gather: every s8 gather moves the
        # reduced chunk set (~n bytes), never W x n
        for c in colls:
            if c.op == "all-gather" and "s8[" in c.line:
                assert c.bytes <= 1.05 * N, (c.bytes, N, c.line[:120])
        # the first hop exists and is quantized
        assert any(c.op == "all-to-all" and "s8[" in c.line
                   for c in colls), [c.line[:80] for c in colls]
    # O(n): W-independent (identical padding here -> identical bytes)
    assert measured[4] == measured[8], measured
    dense = dense_allreduce_ring_bytes(N, 8, dtype_bytes=2)
    assert measured[8] <= 0.6 * dense, (measured[8], dense)
    # and the host-side wire model tracks the compiled truth (the HLO
    # counts collective RESULT bytes, which include each rank's own
    # chunk — W/(W-1) x the true send/recv volume the model reports)
    model, _ = wire_bytes(N, 8, algo=ALGO_TWOHOP)
    assert abs(model * 8 // 7 - measured[8]) <= 0.05 * measured[8], \
        (model, measured[8])


def test_legacy_allgather_exceeds_dense_bf16_at_w4_plus():
    """The motivation pin: the legacy O(W*n) exchange ships MORE bytes
    than a plain dense bf16 ring allreduce whenever W >= 4 — and the
    wire_bytes() model agrees with the compiled program on both
    algorithms (the satellite-1 regression)."""
    for W in (4, 8):
        colls = collect_collectives_full(
            _collective_hlo(N, W, ALGO_ALLGATHER))
        legacy = wire_bytes_of(colls)
        dense = dense_allreduce_ring_bytes(N, W, dtype_bytes=2)
        assert legacy > dense, (W, legacy, dense)
        model, model_dense = wire_bytes(N, W, algo=ALGO_ALLGATHER)
        # HLO counts result bytes (incl. own chunk): W/(W-1) x the model
        assert abs(model * W // (W - 1) - legacy) <= 0.05 * legacy, \
            (W, model, legacy)
        assert model > model_dense          # the model knows it too
        two, _ = wire_bytes(N, W, algo=ALGO_TWOHOP)
        assert two < model_dense            # ... and that two-hop wins


def test_hierarchical_bulk_stays_on_intra_axis():
    """2x4 hierarchical mesh: the ~n-byte quantized hops run in
    replica groups of 4 (the intra sub-axis); every inter-axis
    collective (groups of 2) moves <= ~n/4 bytes — only the reduced
    chunk crosses the slow wire."""
    inter, intra = 2, 4
    mesh = Mesh(np.array(jax.devices()).reshape(inter, intra),
                axis_names=("data_inter", "data_intra"))

    def inner(x):
        return hierarchical_quantized_allreduce_mean(
            x[0], "data_intra", "data_inter", intra, inter)

    g = jax.ShapeDtypeStruct((inter * intra, N), jnp.float32)
    txt = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P(("data_inter", "data_intra")),),
        out_specs=P(), check_vma=False)).lower(g).compile().as_text()
    colls = collect_collectives_full(txt)
    assert colls
    intra_bytes = sum(c.bytes for c in colls if c.group_size == intra)
    inter_bytes = sum(c.bytes for c in colls if c.group_size == inter)
    # intra carries the two ~n int8 hops; inter only the reduced chunk
    assert intra_bytes >= 1.5 * N, (intra_bytes, N)
    assert inter_bytes <= 0.6 * N, (inter_bytes, N)
    for c in colls:
        if c.group_size == inter:
            assert c.bytes <= 0.3 * N, (c.bytes, c.line[:120])
    # per-axis wire model tracks the compiled split (result-bytes
    # convention: x group/(group-1) vs the model's send/recv volume)
    from deepspeed_tpu.runtime.quantized_collectives import \
        wire_bytes_by_axis
    model = wire_bytes_by_axis(N, inter, intra)
    assert abs(model["intra"] * intra // (intra - 1)
               - intra_bytes) <= 0.1 * intra_bytes
    assert abs(model["inter"] * inter // (inter - 1)
               - inter_bytes) <= 0.1 * inter_bytes


def _mlp_engine(cfg_extra, hidden=(64, 256, 64)):
    """Tiny MLP engine (leaves >= one quant block) + a sharded batch."""
    d_in, d_h, d_out = hidden

    def loss_fn(params, batch, rngs=None):
        h = jnp.tanh(batch["x"] @ params["w1"])
        p = h @ params["w2"]
        return jnp.mean((p - batch["y"]) ** 2)

    key = jax.random.PRNGKey(0)
    params = {"w1": jax.random.normal(key, (d_in, d_h)) * 0.1,
              "w2": jax.random.normal(key, (d_h, d_out)) * 0.1}
    engine, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                **cfg_extra})
    shd = NamedSharding(engine.mesh, P(engine._dp_axis_entry))
    rs = np.random.RandomState(0)
    batch = {"x": jax.device_put(rs.randn(32, d_in).astype(np.float32),
                                 shd),
             "y": jax.device_put(rs.randn(32, d_out).astype(np.float32),
                                 shd)}
    P_total = d_in * d_h + d_h * d_out
    return engine, batch, P_total


def _step_hlo(engine, batch):
    return (engine._get_compiled_micro_step()
            .lower(engine.state, batch).compile().as_text())


def test_engine_micro_step_uses_twohop_shape():
    """The production micro step's gradient exchange is the two-hop
    shape: s8 all_to_all present, and no s8 all-gather moves more than
    ~one full parameter set (the legacy W x n gather would be 8x)."""
    engine, batch, P_total = _mlp_engine(
        {"quantized_comm": {"enabled": True}})
    assert engine._quant_allreduce and engine._quant_algo == ALGO_TWOHOP
    colls = collect_collectives_full(_step_hlo(engine, batch))
    s8 = [c for c in colls if "s8[" in c.line]
    assert any(c.op == "all-to-all" for c in s8), \
        [c.line[:80] for c in colls]
    for c in s8:
        assert c.bytes <= 1.2 * P_total, (c.op, c.bytes, P_total)


def test_fused_batch_step_keeps_twohop_shape():
    """The scan-fused batch_step (async_pipeline, gas>=2) carries the
    SAME two-hop gradient exchange inside its scan body: s8 all_to_all
    present, no s8 collective moving more than ~one parameter set per
    iteration — fusing the window must not re-route the wire."""
    engine, batch, P_total = _mlp_engine(
        {"quantized_comm": {"enabled": True},
         "gradient_accumulation_steps": 2})
    assert engine._quant_allreduce
    fused, _why = engine._select_batch_path()
    assert fused
    stacked = jax.device_put(
        jax.tree_util.tree_map(lambda x: np.stack([np.asarray(x)] * 2),
                               batch),
        engine._stacked_batch_sharding())
    txt = (engine._get_compiled_batch_step()
           .lower(engine.state, stacked).compile().as_text())
    colls = collect_collectives_full(txt)
    s8 = [c for c in colls if "s8[" in c.line]
    assert any(c.op == "all-to-all" for c in s8), \
        [c.line[:80] for c in colls]
    for c in s8:
        assert c.bytes <= 1.2 * P_total, (c.op, c.bytes, P_total)


def test_qwz_weight_gather_moves_int8():
    """With quantize_weights, the ZeRO param all-gather moves s8
    elements (+ small fp32 scales) — the bf16 (f32-on-CPU) master
    values never cross the wire at param scale."""
    engine, batch, P_total = _mlp_engine(
        {"quantized_comm": {"enabled": True, "quantize_weights": True},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 2}})
    assert engine._qwz
    colls = collect_collectives_full(_step_hlo(engine, batch))
    s8_gathers = [c for c in colls
                  if c.op == "all-gather" and "s8[" in c.line
                  and c.bytes >= 0.4 * P_total]
    assert s8_gathers, [(c.op, c.bytes) for c in colls]
    # no param-scale float gather remains (floats are f32 on the CPU
    # audit backend, >= 4 bytes/elem -> anything >= 2 bytes/param that
    # is not s8 would be a master/compute-dtype weight gather)
    for c in colls:
        if c.op == "all-gather" and "s8[" not in c.line:
            assert c.bytes < 2 * P_total, (c.bytes, P_total, c.line[:120])


def test_hpz_weight_bytes_cross_inter_only():
    """hierarchical + qwZ + hpZ: every s8 all-gather runs in inter-size
    replica groups (the secondary partition keeps the intra shard), and
    the gradient bulk still rides intra-size groups."""
    inter, intra = 2, 4
    engine, batch, P_total = _mlp_engine(
        {"quantized_comm": {"enabled": True, "quantize_weights": True,
                            "hierarchical": intra,
                            "secondary_partition": True},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 2}})
    assert engine._qwz and engine._hpz and engine._dp_hierarchical
    colls = collect_collectives_full(_step_hlo(engine, batch))
    s8 = [c for c in colls if "s8[" in c.line]
    assert s8
    # weight gathers: s8 all-gathers are inter-group (size 2) only —
    # the intra extent is already locally resident (hpZ)
    weight_gathers = [c for c in s8 if c.op == "all-gather"
                      and c.bytes >= 0.1 * P_total]
    assert weight_gathers
    # gradient bulk on the intra axis: the big s8 all_to_all is
    # intra-group
    grad_a2a = [c for c in s8 if c.op == "all-to-all"]
    assert any(c.group_size == intra for c in grad_a2a), \
        [(c.op, c.bytes, c.group_size) for c in s8]
    inter_bytes = sum(c.bytes for c in s8 if c.group_size == inter)
    intra_bytes = sum(c.bytes for c in s8 if c.group_size == intra)
    assert intra_bytes > inter_bytes, (intra_bytes, inter_bytes)


def _fused_hlo(engine, batch, gas):
    stacked = jax.device_put(
        jax.tree_util.tree_map(lambda x: np.stack([np.asarray(x)] * gas),
                               batch),
        engine._stacked_batch_sharding())
    assert engine._batch_path()
    engine._overlap_path()
    return (engine._get_compiled_batch_step()
            .lower(engine.state, stacked).compile().as_text())


def test_overlapped_fused_step_interleaves_exchange_with_compute():
    """ISSUE 6 acceptance audit: in the OVERLAPPED fused program every
    grad-exchange collective inside the scan body has a dot-general-
    free operand cone — it consumes only the double-buffered carry, so
    the scheduler can interleave it with the iteration's forward/
    backward dots — and the last window's exchange flushes OUTSIDE the
    loop. The serial program is the control: its exchange depends on
    the same iteration's backward (cone contains dots) and nothing
    flushes outside. Dependence, not textual order — backend- and
    scheduler-invariant, like the byte audits above."""
    from deepspeed_tpu.utils.hlo_audit import overlap_structure
    gas = 3

    def build(overlap):
        engine, batch, _ = _mlp_engine(
            {"quantized_comm": {"enabled": True},
             "comm_autotune": {"enabled": True, "overlap": overlap},
             "gradient_accumulation_steps": gas})
        assert engine._quant_allreduce
        return overlap_structure(_fused_hlo(engine, batch, gas))

    o = build(True)
    s = build(False)
    # overlapped: every exchange collective in the body is compute-
    # independent, and the flush exists past the scan
    assert o["exchange_collectives"] >= 2, o
    assert o["overlap_fraction"] == 1.0, o
    assert o["flush_outside_loop"] >= 2, o
    # serial control: same collectives, all compute-dependent, no flush
    assert s["exchange_collectives"] >= 2, s
    assert s["overlap_fraction"] == 0.0, s
    assert s["flush_outside_loop"] == 0, s


def test_overlapped_step_hoists_qwz_weight_gather_out_of_scan():
    """With qwZ, the serial scan body re-gathers the int8 weights every
    iteration; the overlapped step hoists the gather out of the loop
    (params are constant within the window) — the s8 all-gather count
    inside the while body drops and weight-scale gathers appear outside
    it."""
    from deepspeed_tpu.utils.hlo_audit import (hlo_computation_body,
                                               while_body_comps)
    gas = 3

    def s8_gather_counts(txt):
        body_names = while_body_comps(txt)
        inside = outside = 0
        body_lines = []
        for comp in body_names:
            body_lines.extend(hlo_computation_body(txt, comp))
        in_body = {l.strip() for l in body_lines}
        for line in txt.splitlines():
            if "all-gather" in line and "s8[" in line and " = " in line:
                if line.strip() in in_body:
                    inside += 1
                else:
                    outside += 1
        return inside, outside

    def build(overlap):
        engine, batch, P_total = _mlp_engine(
            {"quantized_comm": {"enabled": True,
                                "quantize_weights": True},
             "comm_autotune": {"enabled": True, "overlap": overlap},
             "bf16": {"enabled": True},
             "zero_optimization": {"stage": 2},
             "gradient_accumulation_steps": gas})
        assert engine._qwz
        return s8_gather_counts(_fused_hlo(engine, batch, gas))

    in_o, out_o = build(True)
    in_s, out_s = build(False)
    # hoisting moved the per-iteration weight gathers out of the body
    assert in_o < in_s, (in_o, in_s)
    assert out_o > out_s, (out_o, out_s)


def test_engine_comm_stats_model():
    """The engine's per-step comm telemetry model reports compression
    vs the dense fp32 ring and the active mode string."""
    engine, _, _ = _mlp_engine({"quantized_comm": {"enabled": True}})
    stats = engine._comm_stats
    assert stats is not None and stats["mode"] == "twohop"
    assert stats["compression_ratio"] > 3.0, stats
    dense_engine, _, _ = _mlp_engine({})
    dstats = dense_engine._comm_stats
    assert dstats["mode"] == "dense" and dstats["compression_ratio"] == 1.0
