"""Stochastic-rounding bf16 training (master-weight-free mode).

TPU-native analog of the reference's ``__STOCHASTIC_MODE__`` kernel build
variant (reference setup.py:211-242; ``stochastic_mode`` flag in
ops/transformer/transformer.py there): params live in bf16 end-to-end (no
fp32 master copy) and the optimizer's fp32 update result is cast back to
bf16 with stochastic rounding, so sub-ulp updates accumulate in
expectation instead of RNE-truncating to zero.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.functional import stochastic_round_bf16
from deepspeed_tpu.ops.optimizers import Adam, SGD
from tests.unit.simple_model import (
    base_config, init_simple_params, random_batches, simple_loss_fn)

HIDDEN = 16


def sr_config(**overrides):
    cfg = base_config(
        bf16={"enabled": True, "master_weights": False,
              "stochastic_rounding": True})
    cfg.update(overrides)
    return cfg


class TestSRPrimitive:

    def test_exact_bf16_values_are_fixed_points(self):
        x = jnp.array([1.0, -2.5, 0.0, -0.0, 384.0, 2.0 ** -100],
                      jnp.float32)
        for s in range(8):
            out = stochastic_round_bf16(x, jax.random.PRNGKey(s))
            assert (out == x.astype(jnp.bfloat16)).all()

    def test_unbiased_between_grid_points(self):
        # 1 + 2^-9: remainder is 1/4 of the bf16 ulp at 1.0 (2^-7), so
        # E[sr(x)] == x and P(round up) == 0.25
        x = jnp.full((40000,), 1.0 + 2 ** -9, jnp.float32)
        out = stochastic_round_bf16(x, jax.random.PRNGKey(0))
        mean = float(out.astype(jnp.float32).mean())
        assert abs(mean - float(x[0])) < 3e-4
        p_up = float((out.astype(jnp.float32) > 1.0).mean())
        assert abs(p_up - 0.25) < 0.02

    def test_nonfinite_passthrough(self):
        x = jnp.array([np.inf, -np.inf, np.nan], jnp.float32)
        out = stochastic_round_bf16(x, jax.random.PRNGKey(1))
        assert jnp.isposinf(out[0]) and jnp.isneginf(out[1])
        assert jnp.isnan(out[2])

    def test_deterministic_for_fixed_key(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (256,), jnp.float32)
        a = stochastic_round_bf16(x, jax.random.PRNGKey(3))
        b = stochastic_round_bf16(x, jax.random.PRNGKey(3))
        assert (a == b).all()


class TestSROptimizer:
    """The defining property: repeated sub-ulp updates move bf16 params
    under SR (in expectation) but freeze under plain RNE casting."""

    def _run_sgd(self, sr: bool, steps=400, delta=1e-3):
        # bf16 ulp at 1.0 is 2^-7 = 7.8e-3; a 1e-3 step is sub-ulp, so an
        # RNE cast of 1.0 - 1e-3... rounds back to 1.0 every single step.
        opt = SGD(lr=1.0)
        p = {"w": jnp.ones((64,), jnp.bfloat16)}
        st = opt.init(p)
        g = {"w": jnp.full((64,), delta, jnp.float32)}
        for i in range(steps):
            kw = {"sr_key": jax.random.PRNGKey(i)} if sr else {}
            p, st = opt.update(g, st, p, **kw)
        return float(np.mean(np.asarray(p["w"], np.float32)))

    def test_rne_freezes_sub_ulp_updates(self):
        assert self._run_sgd(sr=False) == 1.0

    def test_sr_accumulates_sub_ulp_updates(self):
        final = self._run_sgd(sr=True)
        # expected drift: 400 steps * 1e-3 = 0.4 -> ~0.6
        assert final < 0.8, final
        assert abs(final - 0.6) < 0.1, final

    def test_adam_sr_matches_fp32_reference_in_expectation(self):
        # one Adam step from identical state: the SR bf16 result must be
        # an unbiased rounding of the fp32 result
        opt = Adam(lr=1e-2)
        key = jax.random.PRNGKey(0)
        w32 = jax.random.normal(key, (4096,), jnp.float32)
        g = {"w": jax.random.normal(jax.random.fold_in(key, 1), (4096,),
                                    jnp.float32)}
        p32, _ = opt.update(g, opt.init({"w": w32}), {"w": w32})
        pbf = {"w": w32.astype(jnp.bfloat16)}
        acc = np.zeros((4096,), np.float64)
        n = 32
        for i in range(n):
            out, _ = opt.update(g, opt.init(pbf), pbf,
                                sr_key=jax.random.PRNGKey(100 + i))
            acc += np.asarray(out["w"], np.float64)
        mean_sr = acc / n
        ref = np.asarray(p32["w"], np.float64)
        # mean over keys approaches the fp32 target much tighter than one
        # bf16 ulp (~2^-8 relative)
        err = np.abs(mean_sr - ref).mean()
        scale = np.abs(ref).mean()
        assert err < 1.5e-3 * max(scale, 1.0), (err, scale)


class TestSREngine:

    def test_params_are_bf16_no_fp32_master(self):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params,
            config=sr_config())
        leaves = jax.tree_util.tree_leaves(engine.state.params)
        assert all(l.dtype == jnp.bfloat16 for l in leaves)
        # moments stay fp32
        m_leaves = jax.tree_util.tree_leaves(engine.state.opt_state.exp_avg)
        assert all(l.dtype == jnp.float32 for l in m_leaves)

    def test_loss_decreases_master_free(self):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params,
            config=sr_config())
        batches = random_batches(30, 16, HIDDEN)
        it = iter(batches)
        losses = [float(engine.train_batch(it)) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.7, losses

    def test_master_free_tracks_fp32_master_loss(self):
        """Same data, same init: the master-free bf16 run's final loss must
        stay close to the fp32-master bf16 run's (the whole point of SR)."""
        def run(cfg):
            params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
            engine, *_ = deepspeed_tpu.initialize(
                model=simple_loss_fn, model_parameters=params, config=cfg)
            it = iter(random_batches(40, 16, HIDDEN))
            return [float(engine.train_batch(it)) for _ in range(40)]

        ref = run(base_config(bf16={"enabled": True}))
        mf = run(sr_config())
        assert mf[-1] < ref[-1] * 1.5 + 1e-3, (ref[-1], mf[-1])

    def test_zero2_composition(self):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params,
            config=sr_config(zero_optimization={"stage": 2}))
        it = iter(random_batches(20, 16, HIDDEN))
        losses = [float(engine.train_batch(it)) for _ in range(20)]
        assert losses[-1] < losses[0] * 0.8, losses
        leaves = jax.tree_util.tree_leaves(engine.state.params)
        assert all(l.dtype == jnp.bfloat16 for l in leaves)

    def test_checkpoint_roundtrip_keeps_bf16(self, tmp_path):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params,
            config=sr_config())
        it = iter(random_batches(5, 16, HIDDEN))
        for _ in range(5):
            engine.train_batch(it)
        engine.save_checkpoint(str(tmp_path), tag="sr")
        params2 = init_simple_params(jax.random.PRNGKey(1), HIDDEN)
        engine2, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params2,
            config=sr_config())
        engine2.load_checkpoint(str(tmp_path), tag="sr")
        a = jax.tree_util.tree_leaves(engine.state.params)
        b = jax.tree_util.tree_leaves(engine2.state.params)
        for x, y in zip(a, b):
            assert x.dtype == jnp.bfloat16 and y.dtype == jnp.bfloat16
            assert (np.asarray(x) == np.asarray(y)).all()

    def test_config_validation(self):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError
        with pytest.raises(DeepSpeedConfigError):
            deepspeed_tpu.initialize(
                model=simple_loss_fn, model_parameters=params,
                config=base_config(
                    bf16={"enabled": True, "master_weights": False}))
        with pytest.raises(DeepSpeedConfigError):
            deepspeed_tpu.initialize(
                model=simple_loss_fn, model_parameters=params,
                config=base_config(
                    bf16={"enabled": False, "stochastic_rounding": True}))
        with pytest.raises(DeepSpeedConfigError):
            deepspeed_tpu.initialize(
                model=simple_loss_fn, model_parameters=params,
                config=sr_config(
                    zero_optimization={"stage": 2, "cpu_offload": True}))


class TestMinimalMemoryCompose:
    """Adam8bit x master-free bf16 x stochastic rounding — the
    minimal-memory training configuration (int8 moments, bf16 params,
    no fp32 master) must train end to end through the engine."""

    def test_adam8bit_master_free_trains(self):
        import deepspeed_tpu as ds
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        cfg = sr_config(optimizer={"type": "Adam8bit",
                                   "params": {"lr": 1e-2}})
        eng, *_ = ds.initialize(model=simple_loss_fn,
                                model_parameters=params, config=cfg)
        losses = [float(eng.train_batch(iter([b])))
                  for b in random_batches(40, 8, HIDDEN, seed=0)]
        assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
        # params stayed bf16 and the quantized moments are int8
        leaves = jax.tree_util.tree_leaves(eng.state.params)
        assert all(x.dtype == jnp.bfloat16 for x in leaves)
        from deepspeed_tpu.ops.optimizers import Adam8bitState
        st = eng.state.opt_state
        assert isinstance(st, Adam8bitState)
        assert all(x.dtype == jnp.int8
                   for x in jax.tree_util.tree_leaves(st.m_codes))
