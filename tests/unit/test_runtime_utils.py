"""Runtime utils tests (mirror reference tests/unit/test_runtime_utils.py +
test_partition.py: CheckOverflow, norms, PartitionedTensor round-trips incl.
in-jit all_gather mode under shard_map)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime import utils as ds_utils


def test_check_overflow_basic():
    co = ds_utils.CheckOverflow()
    good = {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))}
    bad = {"a": jnp.ones((4,)), "b": jnp.array([1.0, jnp.inf])}
    nan = {"a": jnp.array([jnp.nan])}
    assert not bool(co.has_overflow(good))
    assert bool(co.has_overflow(bad))
    assert bool(co.has_overflow(nan))


def test_check_overflow_via_norm():
    co = ds_utils.CheckOverflow()
    assert bool(co.check_using_norm([2.0, -1.0]))
    assert not bool(co.check_using_norm([2.0, 3.0]))


def test_check_overflow_in_jit_with_axis():
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("model",))
    co = ds_utils.CheckOverflow(axis_names=("model",))

    @jax.jit
    def f(x):
        def inner(xs):
            return co.has_overflow({"g": xs})
        return shard_map(inner, mesh=mesh, in_specs=P("model"),
                         out_specs=P())(x)

    x = np.ones((8,), np.float32)
    assert not bool(f(x))
    x[6] = np.inf  # lives on one shard only; pmax must propagate
    assert bool(f(x))


def test_grad_norm_conventions():
    g = {"w": jnp.array([3.0, 4.0])}
    np.testing.assert_allclose(ds_utils.get_grad_norm(g), 5.0, rtol=1e-6)
    np.testing.assert_allclose(
        ds_utils.get_grad_norm(g, norm_type=float("inf")), 4.0)
    bad = {"w": jnp.array([jnp.inf])}
    assert float(ds_utils.get_grad_norm(bad)) == -1.0
    assert float(ds_utils.get_weight_norm(bad)) == -1.0


def test_partitioned_tensor_eager_roundtrip():
    x = jnp.arange(10.0)
    parts = []
    metas = []
    for rank in range(3):
        pt = ds_utils.PartitionedTensor(x, num_parts=3, rank=rank)
        parts.append(pt.data())
        metas.append(pt.to_meta())
    # uneven split: partition_uniform boundaries
    assert sum(p.shape[0] for p in parts) == 10
    # reconstruct on the consumer side from meta + parts (ref from_meta:391)
    pt0 = ds_utils.PartitionedTensor.from_meta(metas[1], parts[1])
    full = pt0.full(parts=parts)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(x))
    assert pt0.full_size() == (10,)
    assert pt0.rank == 1 and pt0.num_parts == 3


def test_partitioned_tensor_meta_encoding():
    x = jnp.ones((4, 6))
    pt = ds_utils.PartitionedTensor(x, num_parts=2, rank=0)
    meta = pt.to_meta()
    assert meta.dtype == np.int64
    assert list(meta[:3]) == [2, 4, 6]  # ndims, shape


def test_partitioned_tensor_in_jit_allgather():
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("model",))
    x = jnp.arange(22.0)  # not divisible by 4: padded chunks

    @jax.jit
    def f(x):
        def inner(x_full):
            # every shard sees the full replicated tensor, partitions it,
            # keeps its slice, then reconstructs via all_gather
            pt = ds_utils.PartitionedTensor(x_full[0], num_parts=4,
                                            axis_name="model")
            return pt.full()[None]
        return shard_map(inner, mesh=mesh, in_specs=P(None),
                         out_specs=P(None), check_vma=False)(x[None])

    np.testing.assert_array_equal(np.asarray(f(x))[0], np.asarray(x))


def test_memory_status_and_see_memory_usage(caplog):
    ds_utils.memory_status("probe")
    ds_utils.see_memory_usage("probe", force=True)
    ds_utils.see_memory_usage("skipped", force=False)


def test_call_to_str():
    assert ds_utils.call_to_str("f", 1, "a", k=2) == "f(1, 'a', k=2)"
    assert ds_utils.call_to_str("g") == "g()"


def test_set_random_seed_returns_key():
    k = ds_utils.set_random_seed(7)
    v1 = jax.random.normal(k, (3,))
    v2 = jax.random.normal(ds_utils.set_random_seed(7), (3,))
    np.testing.assert_array_equal(v1, v2)
