"""Hybrid banded+residual sparse attention (BigBird fast path).

deepspeed_tpu/ops/sparse_attention/hybrid.py: the banded kernels run
the maximal global-prefix + band sub-pattern, the v2 walk runs the
random-block residue, and the parts merge by per-part log-sum-exp.
Reference capability being matched: BigBirdSparsityConfig layouts
(deepspeed/ops/sparse_attention/sparsity_config.py:421) at sparse — not
overhead-bound generic — cost. Numerics are pinned against the
dense-masked oracle, including backward through the merged lse.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
from deepspeed_tpu.ops.sparse_attention import hybrid as hy
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig,
    VariableSparsityConfig)


@pytest.fixture(autouse=True)
def _fresh_cache():
    # this module tests the LEGACY hybrid dispatch, kept as a numerics
    # oracle behind the flag since the unified masked kernel (PR 11)
    # became the default
    bs._FN_CACHE.clear()
    old_masked = bs.USE_MASKED_FLASH
    bs.USE_MASKED_FLASH = False
    yield
    bs.USE_MASKED_FLASH = old_masked
    bs._FN_CACHE.clear()


def _rand_qkv(B, H, S, D, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return [jax.random.normal(k, (B, H, S, D), dtype) for k in ks]


def _bigbird(H=2, block=32, per_head=False, seed=0):
    return BigBirdSparsityConfig(
        num_heads=H, block=block, different_layout_per_head=per_head,
        num_random_blocks=1, num_sliding_window_blocks=3,
        num_global_blocks=1, seed=seed)


# --------------------------------------------------------------------- #
# detection / planning
# --------------------------------------------------------------------- #
def test_detect_subpattern_bigbird():
    L = _bigbird().make_layout(512)
    params, residual, coverage = hy.detect_banded_subpattern(L)
    assert (params.g_r, params.g_c, params.w, params.causal) == \
        (1, 1, 1, False)
    # the predicate + residual must reconstruct the layout exactly, and
    # be disjoint
    n = L.shape[1]
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    pred = ((rb < params.g_r) | (cb < params.g_c) |
            (np.abs(rb - cb) <= params.w))
    rec = pred[None] | residual.astype(bool)
    assert (rec == L.astype(bool)).all()
    assert not (pred[None] & residual.astype(bool)).any()
    assert coverage > 0.8


def test_detect_subpattern_per_head_random():
    """Per-head random blocks: the banded part is fit under the head
    INTERSECTION; each head's residual keeps its own random blocks."""
    L = _bigbird(H=4, per_head=True).make_layout(512)
    params, residual, _cov = hy.detect_banded_subpattern(L)
    assert (params.g_r, params.g_c, params.w) == (1, 1, 1)
    n = L.shape[1]
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    pred = ((rb < 1) | (cb < 1) | (np.abs(rb - cb) <= 1))
    for h in range(4):
        assert ((pred | residual[h].astype(bool))
                == L[h].astype(bool)).all()


def test_plan_declines_when_banded_owns_it():
    """Pure Longformer (no residual) must go to the exact banded path,
    not the hybrid."""
    L = BSLongformerSparsityConfig(num_heads=2, block=32).make_layout(512)
    assert hy.plan_hybrid(L, 32, True) is None
    assert bs.planned_kernel(L, 32, interpret=True) == "banded"


def test_plan_declines_low_coverage():
    """Random-heavy layout (residual dominates): the banded pass would
    be pure overhead."""
    rng = np.random.default_rng(0)
    n = 16
    L = (rng.random((1, n, n)) < 0.5).astype(np.int32)
    L |= np.eye(n, dtype=np.int32)[None]          # keep a w=0 diagonal
    det = hy.detect_banded_subpattern(L)
    if det is not None:
        assert det[2] < hy._MIN_COVERAGE
    assert hy.plan_hybrid(L, 32, True) is None


def test_plan_declines_unstreamable_block_compiled():
    """Compiled mode requires the v2 walk to DMA-stream the residual:
    non-128-multiple fine blocks decline (same constraint as v2)."""
    L = _bigbird(block=64).make_layout(4096)
    assert hy.plan_hybrid(L, 64, interpret=False) is None
    assert hy.plan_hybrid(L, 64, interpret=True) is not None


def test_dispatch_plans_hybrid_for_bigbird():
    L = _bigbird().make_layout(512)
    assert bs.planned_kernel(L, 32, interpret=True) == "hybrid"
    f = bs._sparse_attention_fn(L, 32, 0.25, has_am=False, interpret=True)
    assert getattr(f, "kernel_kind", None) == "hybrid"
    assert f.hybrid_coverage > 0.8
    # flipping the switch falls back to the generic family
    old = bs.USE_HYBRID
    try:
        bs.USE_HYBRID = False
        bs._FN_CACHE.clear()
        assert bs.planned_kernel(L, 32, interpret=True) != "hybrid"
    finally:
        bs.USE_HYBRID = old


# --------------------------------------------------------------------- #
# numerics vs the dense oracle
# --------------------------------------------------------------------- #
def _check_fwd_bwd(L, B=1, H=2, S=512, D=16, dtype=jnp.float32,
                   atol=5e-6, seed=0, **kw):
    assert bs.planned_kernel(L, S // L.shape[1], interpret=True) == \
        "hybrid"
    q, k, v = _rand_qkv(B, H, S, D, seed=seed, dtype=dtype)

    def loss_h(q, k, v):
        return jnp.sum(bs.block_sparse_attention(
            q, k, v, L, interpret=True, **kw).astype(jnp.float32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(bs.block_sparse_attention_reference(
            q, k, v, L, **kw).astype(jnp.float32) ** 2)

    o = bs.block_sparse_attention(q, k, v, L, interpret=True, **kw)
    o_ref = bs.block_sparse_attention_reference(q, k, v, L, **kw)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_ref, np.float32),
                               atol=atol, rtol=atol)
    gh = jax.grad(loss_h, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gh, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=atol * 20, rtol=atol * 20, err_msg=f"d{name}")


def test_hybrid_matches_oracle_bigbird():
    _check_fwd_bwd(_bigbird().make_layout(512))


def test_hybrid_matches_oracle_per_head():
    _check_fwd_bwd(_bigbird(H=2, per_head=True, seed=3).make_layout(512))


def test_hybrid_matches_oracle_more_random():
    cfg = BigBirdSparsityConfig(num_heads=2, block=32,
                                num_random_blocks=2,
                                num_sliding_window_blocks=5,
                                num_global_blocks=2, seed=7)
    _check_fwd_bwd(cfg.make_layout(512), seed=5)


def test_hybrid_matches_oracle_causal_residual():
    """Causal band + random lower-triangle residue: the clip flows into
    both the banded predicate and the merge."""
    n = 16
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    pred = (((rb < 1) | (cb < 1) | (np.abs(rb - cb) <= 1)) &
            (cb <= rb))
    L = np.broadcast_to(pred, (2, n, n)).copy()
    rng = np.random.default_rng(11)
    for h in range(2):
        for r in range(4, n):
            c = rng.integers(1, r - 1)
            L[h, r, c] = True
    L = L.astype(np.int32)
    det = hy.detect_banded_subpattern(L)
    assert det is not None and det[0].causal
    _check_fwd_bwd(L, seed=2)


def test_variable_chunked_windows_decline_hybrid():
    """VariableSparsityConfig's local windows are block-diagonal CHUNKS,
    not a sliding band — only the w=0 diagonal survives the subpattern
    fit, coverage lands under _MIN_COVERAGE, and the layout stays on
    the generic family (which still matches the oracle)."""
    cfg = VariableSparsityConfig(num_heads=2, block=32,
                                 num_random_blocks=1,
                                 local_window_blocks=[3],
                                 global_block_indices=[0])
    L = cfg.make_layout(512)
    det = hy.detect_banded_subpattern(L)
    assert det is not None and det[2] < hy._MIN_COVERAGE
    assert hy.plan_hybrid(L, 32, True) is None
    planned = bs.planned_kernel(L, 32, interpret=True)
    assert planned != "hybrid"
    q, k, v = _rand_qkv(1, 2, 512, 16, seed=4)
    o = bs.block_sparse_attention(q, k, v, L, interpret=True)
    o_ref = bs.block_sparse_attention_reference(q, k, v, L)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               atol=5e-5, rtol=5e-5)


def test_hybrid_with_key_padding_mask():
    L = _bigbird().make_layout(512)
    kpm = np.zeros((1, 512), np.float32)
    kpm[:, 480:] = -1e9
    _check_fwd_bwd(L, key_padding_mask=jnp.asarray(kpm),
                   key_padding_mask_mode="add")


def test_hybrid_bf16():
    L = _bigbird().make_layout(512)
    q, k, v = _rand_qkv(1, 2, 512, 16, seed=6, dtype=jnp.bfloat16)
    o = bs.block_sparse_attention(q, k, v, L, interpret=True)
    o_ref = bs.block_sparse_attention_reference(q, k, v, L)
    assert o.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_ref, np.float32),
                               atol=3e-2, rtol=3e-2)


# --------------------------------------------------------------------- #
# FLOP accounting (VERDICT r4 #3: <= 2x the exact-sparse bound at
# BigBird density)
# --------------------------------------------------------------------- #
def test_hybrid_stats_bigbird_bench_geometry():
    """At the bench geometry (S=8192, 128 blocks, BigBird defaults) the
    hybrid computes <= 2x the exact-sparse cell-dot bound at the
    PLANNED walk tiles (VERDICT r4 #3 bar), and the fine 128-tile walk
    sits essentially AT the bound (<= 1.1x) — the per-step-overhead vs
    FLOP-waste trade between them is the hardware sweep's call
    (tools/ab_coarse_sparse.py)."""
    L = _bigbird(H=16, block=128).make_layout(8192)
    plan = hy.plan_hybrid(L, 128, interpret=False)
    assert plan is not None, "hybrid must engage at the bench geometry"
    stats = hy.hybrid_stats(L, 128, plan)
    assert stats["exact_cell_dots"] > 0
    assert stats["waste"] <= 2.0, stats
    fine = hy.hybrid_stats(L, 128,
                           plan._replace(blocks=(128, 128)))
    assert fine["waste"] <= 1.1, fine
    # and the hybrid is the planned kernel there
    assert bs.planned_kernel(L, 128, interpret=False) == "hybrid"


def test_detect_subpattern_fuzz_invariants():
    """Property fuzz over planted banded structure + random residue:
    detection must always return a predicate that is a SUBSET of every
    head's layout, disjoint from the residual, reconstructing the
    layout exactly, and covering at least the planted banded cells
    (it may legally absorb coincidentally-full diagonals/rows)."""
    rng = np.random.default_rng(42)
    detected = 0
    for trial in range(40):
        n = int(rng.integers(4, 24))
        H = int(rng.integers(1, 4))
        g_r = int(rng.integers(0, max(n // 3, 1)))
        g_c = int(rng.integers(0, max(n // 3, 1)))
        w = int(rng.integers(0, max(n // 3, 1)))
        causal = bool(rng.integers(0, 2))
        idx = np.arange(n)
        rb, cb = idx[:, None], idx[None, :]
        clip = (cb <= rb) if causal else np.ones((n, n), bool)
        pred = (((rb < g_r) | (cb < g_c) | (np.abs(rb - cb) <= w))
                & clip)
        L = np.broadcast_to(pred, (H, n, n)).copy()
        # plant a few random residue blocks per head (inside the clip)
        for h in range(H):
            for _ in range(int(rng.integers(0, 4))):
                r = int(rng.integers(0, n))
                c = int(rng.integers(0, r + 1)) if causal \
                    else int(rng.integers(0, n))
                L[h, r, c] = True
        L = L.astype(np.int32)
        det = hy.detect_banded_subpattern(L)
        if det is None:
            # legal only when no full diagonal survives the fit
            continue
        detected += 1
        p, residual, coverage = det
        dp_clip = (cb <= rb) if p.causal else np.ones((n, n), bool)
        dpred = (((rb < p.g_r) | (cb < p.g_c) |
                  (np.abs(rb - cb) <= p.w)) & dp_clip)
        for h in range(H):
            lh = L[h].astype(bool)
            assert (dpred <= lh).all(), (trial, p)           # subset
            assert not (dpred & residual[h].astype(bool)).any(), trial
            assert ((dpred | residual[h].astype(bool)) == lh).all(), \
                (trial, p)
        # the fit must COVER the planted banded structure (it may
        # absorb more via coincidentally-full diagonals, never less) —
        # guards a regression to trivial w=0/g=0 fits
        if p.causal == causal:
            assert (pred <= dpred).all(), (trial, p,
                                           (g_r, g_c, w, causal))
        assert 0.0 < coverage <= 1.0
    # detection must actually fire on planted-banded layouts — a
    # regression to always-None would otherwise pass vacuously
    assert detected >= 30, detected


def test_hybrid_stats_account_all_parts():
    L = _bigbird().make_layout(512)
    plan = hy.plan_hybrid(L, 32, True)
    stats = hy.hybrid_stats(L, 32, plan)
    assert stats["residual_nnz_blocks"] == int(plan.residual.sum())
    assert stats["computed_cell_dots"] >= stats["exact_cell_dots"]
    assert stats["coverage"] == plan.coverage
