"""int8 block-quantized gradient allreduce (TPU-native extension;
ZeRO++-style comm compression, runtime/quantized_collectives.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.quantized_collectives import (
    ALGO_ALLGATHER, ALGO_TWOHOP, dequantize_blockwise,
    hierarchical_quantized_allreduce_mean, quantize_blockwise,
    quantized_allreduce_mean, wire_bytes, wire_bytes_by_axis)


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1000).astype(np.float32) * 3)
    q, s, n = quantize_blockwise(x, block=256)
    y = dequantize_blockwise(q, s, n)
    # per-element error <= absmax_of_block / 127 (half-step rounding x2)
    err = np.abs(np.asarray(y) - np.asarray(x))
    bound = np.abs(np.asarray(x)).max() / 127 + 1e-7
    assert err.max() <= bound


@pytest.mark.parametrize("algo", [ALGO_ALLGATHER, ALGO_TWOHOP])
def test_allreduce_mean_matches_dense_within_quant_error(algo):
    mesh = build_mesh({"data": 8})
    rng = np.random.RandomState(1)
    g = jnp.asarray(rng.randn(8, 512).astype(np.float32))

    def inner(x):
        return quantized_allreduce_mean(x[0], "data", algo=algo,
                                        world_size=8)

    out = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_vma=False))(g)
    dense = np.asarray(g).mean(axis=0)
    np.testing.assert_allclose(np.asarray(out), dense, atol=0.05)


@pytest.mark.parametrize("n", [1, 255, 256, 257, 999, 2048 + 17])
def test_twohop_odd_sizes_and_padding(n):
    """Sizes around the block/world-chunk boundaries survive the pad ->
    chunk -> all_to_all -> gather -> unpad round trip exactly."""
    mesh = build_mesh({"data": 8})
    rng = np.random.RandomState(n)
    g = jnp.asarray(rng.randn(8, n).astype(np.float32))

    def inner(x):
        return quantized_allreduce_mean(x[0], "data", algo=ALGO_TWOHOP,
                                        world_size=8)

    out = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_vma=False))(g)
    assert out.shape == (n,)
    dense = np.asarray(g).mean(axis=0)
    # two quantization passes (worker + reduced chunk): 2x the one-pass
    # bound of absmax/127 per pass
    bound = 2 * np.abs(np.asarray(g)).max() / 127 + 1e-6
    assert np.abs(np.asarray(out) - dense).max() <= bound


def test_twohop_preserves_2d_shape_and_dtype():
    mesh = build_mesh({"data": 8})
    rng = np.random.RandomState(3)
    g = jnp.asarray(rng.randn(8, 33, 17).astype(np.float32))

    def inner(x):
        return quantized_allreduce_mean(x[0], "data", algo=ALGO_TWOHOP,
                                        world_size=8)

    out = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_vma=False))(g)
    assert out.shape == (33, 17) and out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(g).mean(axis=0), atol=0.08)


def test_hierarchical_matches_dense_within_quant_error():
    """2x4 hierarchical two-hop == flat dense mean within the (three
    quantization passes) error bound."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4),
                axis_names=("data_inter", "data_intra"))
    rng = np.random.RandomState(5)
    g = jnp.asarray(rng.randn(8, 777).astype(np.float32))

    def inner(x):
        return hierarchical_quantized_allreduce_mean(
            x[0], "data_intra", "data_inter", 4, 2)

    out = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P(("data_inter", "data_intra")),),
        out_specs=P(), check_vma=False))(g)
    dense = np.asarray(g).mean(axis=0)
    bound = 3 * np.abs(np.asarray(g)).max() / 127 + 1e-6
    assert np.abs(np.asarray(out) - dense).max() <= bound


def test_wire_volume_models_full_algorithm():
    """Satellite-1 regression: wire_bytes models the TOTAL per-rank
    payload of the actual algorithm. The legacy all_gather path exceeds
    a dense bf16 ring allreduce at every W >= 4; the two-hop path never
    does, and is W-independent."""
    n = 1_000_000
    for W in (4, 8, 32):
        legacy, dense = wire_bytes(n, W, algo=ALGO_ALLGATHER)
        assert legacy > dense, (W, legacy, dense)      # compression defeated
        two, dense2 = wire_bytes(n, W, algo=ALGO_TWOHOP)
        assert dense2 == dense
        assert two < dense, (W, two, dense)
    # dp=2 is the one world where the legacy single-hop still beats bf16
    legacy2, dense_w2 = wire_bytes(n, 2, algo=ALGO_ALLGATHER)
    assert legacy2 < dense_w2
    # O(n): the two-hop payload is independent of W (same padding)
    two4, _ = wire_bytes(n, 4, block=250)
    two8, _ = wire_bytes(n, 8, block=250)
    assert abs(two4 - two8) / two8 < 0.2, (two4, two8)
    # vs fp32 grads the two-hop still compresses ~3.7x
    two, _ = wire_bytes(n, 8)
    _, dense_fp32 = wire_bytes(n, 8, dense_dtype_bytes=4)
    assert dense_fp32 / two > 3.4
    # hierarchical split: slow-axis bytes ~ 1/intra of fast-axis bytes
    split = wire_bytes_by_axis(n, 2, 4)
    assert split["inter"] < 0.4 * split["intra"], split
    hier_total, _ = wire_bytes(n, 8, hierarchical=(2, 4))
    assert hier_total == split["intra"] + split["inter"]


def test_engine_trains_and_converges():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    eq, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "compressed_allreduce": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert eq._quant_allreduce
    ed, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    lq, ld = [], []
    for i in range(10):
        b = random_batches(1, 32, 8, seed=i)[0]
        lq.append(float(eq.train_batch(iter([b]))))
        ld.append(float(ed.train_batch(iter([b]))))
    assert lq[-1] < lq[0]                       # converges
    np.testing.assert_allclose(lq, ld, rtol=0.2)  # tracks the dense run


def test_fp16_overflow_survives_quantization():
    """An fp16 overflow (inf grads) must still trip the skip-step
    machinery — quantization alone would launder inf into garbage."""
    from tests.unit.simple_model import init_simple_params, random_batches

    def exploding_loss(params, batch):
        x = batch["x"] * 1e4  # fp16 overflow in the first matmul
        for i in range(len(params)):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"].astype(x.dtype) + layer["b"].astype(x.dtype)
        return jnp.mean(x.astype(jnp.float32) ** 2)

    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=exploding_loss, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "compressed_allreduce": {"enabled": True},
                "fp16": {"enabled": True, "initial_scale_power": 20},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    b = random_batches(1, 32, 8)[0]
    before = jax.tree_util.tree_map(np.asarray, e.state.params)
    e.train_batch(iter([b]))
    assert e.skipped_steps >= 1          # overflow detected -> skipped
    for a, c in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(e.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_quantized_composes_with_zero2_and_accumulation():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "compressed_allreduce": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    losses = []
    for i in range(4):
        bs = random_batches(2, 32, 8, seed=i)
        losses.append(float(e.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_twohop_composes_with_zero2_and_accumulation():
    """The qgZ two-hop exchange (explicit quantized_comm config) under
    ZeRO-2 + gradient accumulation converges and tracks finite losses —
    leaves >= one block actually ride the quantized exchange (hidden_dim
    chosen so w leaves are 1024 elems > block 256)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=32)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "quantized_comm": {"enabled": True, "algo": "twohop"},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert e._quant_allreduce and e._quant_algo == "twohop"
    assert any(l.size >= e._quant_block for l in
               jax.tree_util.tree_leaves(e.state.params))
    losses = []
    for i in range(4):
        bs = random_batches(2, 32, 32, seed=i)
        losses.append(float(e.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_hierarchical_composes_with_zero2_and_accumulation():
    """quantized_comm.hierarchical splits the mesh into
    data_inter x data_intra; ZeRO-2 + grad accumulation still trains,
    and the run tracks a flat-mesh two-hop run closely."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=32)

    def engine(hier):
        qc = {"enabled": True}
        if hier:
            qc["hierarchical"] = 4
        e, *_ = ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 2,
                    "quantized_comm": qc,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
        return e

    eh, ef = engine(True), engine(False)
    assert eh._dp_hierarchical and eh.dp_world_size == 8
    assert dict(eh.mesh.shape) == {"data_inter": 2, "data_intra": 4}
    lh, lf = [], []
    for i in range(4):
        bs = random_batches(2, 32, 32, seed=i)
        lh.append(float(eh.train_batch(iter(bs))))
        lf.append(float(ef.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in lh)
    assert lh[-1] < lh[0]
    np.testing.assert_allclose(lh, lf, rtol=0.1)


def test_qwz_weight_quantization_trains():
    """qwZ (int8 weight gather) + hpZ (secondary partition) on the
    hierarchical mesh: trains, converges, and tracks the plain bf16
    ZeRO-2 run within the weight-quantization tolerance."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=32)

    def engine(qc):
        e, *_ = ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "bf16": {"enabled": True},
                    "quantized_comm": qc,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
        return e

    eq = engine({"enabled": True, "quantize_weights": True,
                 "hierarchical": 4, "secondary_partition": True})
    e0 = engine({"enabled": True})
    assert eq._qwz and eq._hpz
    lq, l0 = [], []
    for i in range(8):
        b = random_batches(1, 32, 32, seed=i)[0]
        lq.append(float(eq.train_batch(iter([b]))))
        l0.append(float(e0.train_batch(iter([b]))))
    assert all(np.isfinite(l) for l in lq)
    assert lq[-1] < lq[0]
    np.testing.assert_allclose(lq, l0, rtol=0.25)


def test_disabled_hierarchical_leaves_mesh_flat():
    """quantized_comm disabled must be a true no-op: a leftover
    hierarchical knob must not split the mesh (user code keyed on the
    flat 'data' axis keeps working)."""
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "quantized_comm": {"enabled": False, "hierarchical": 4},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert dict(e.mesh.shape) == {"data": 8}
    assert not e._dp_hierarchical and not e._quant_allreduce


def test_twohop_forward_backward_step_facade():
    """The reference-style forward()/backward()/step() facade rides the
    same quantized exchange as train_batch (and keeps qwZ outside
    autodiff)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=32)

    def engine(qc):
        e, *_ = ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "bf16": {"enabled": True},
                    "quantized_comm": qc,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
        return e

    e = engine({"enabled": True, "quantize_weights": True})
    assert e._quant_allreduce and e._qwz
    losses = []
    for i in range(6):
        b = random_batches(1, 32, 32, seed=i)[0]
        loss = e.forward(b)
        e.backward(loss)
        e.step()
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    # qwZ differentiated through round() would zero the grads and stall:
    # convergence here proves the cast stayed outside autodiff
    assert losses[-1] < losses[0]


def test_secondary_partition_requires_hierarchical():
    from deepspeed_tpu.runtime.config import DeepSpeedConfigError
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    with pytest.raises(DeepSpeedConfigError):
        ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "quantized_comm": {"enabled": True,
                                       "secondary_partition": True},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})


def test_invalid_hierarchical_combinations_rejected_at_config_time():
    """Bad combinations die as DeepSpeedConfigError during config
    parsing (not as late engine asserts): legacy algo with hierarchical,
    sparse_gradients, OnebitAdam, and a mesh.axes data_intra that
    contradicts the hierarchical knob."""
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    base = {"train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    bad = [
        {**base, "quantized_comm": {"enabled": True, "hierarchical": 4,
                                    "algo": "allgather"}},
        {**base, "sparse_gradients": True,
         "quantized_comm": {"enabled": True, "hierarchical": 4}},
        {**base, "optimizer": {"type": "OneBitAdam",
                               "params": {"lr": 1e-2}},
         "quantized_comm": {"enabled": True, "hierarchical": 4}},
    ]
    for cfg in bad:
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(cfg, world_size=8)
    # explicit mesh.axes split disagreeing with the hierarchical knob
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    with pytest.raises(ValueError):
        ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={**base,
                    "mesh": {"axes": {"data_inter": 4, "data_intra": 2}},
                    "quantized_comm": {"enabled": True,
                                       "hierarchical": 4}})


def test_legacy_compressed_allreduce_config_still_works():
    """The pre-rewrite 'compressed_allreduce' block keeps working as an
    alias of quantized_comm {enabled, block}."""
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "compressed_allreduce": {"enabled": True, "block": 128},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert e._quant_allreduce and e._quant_block == 128
    assert e._quant_algo == "twohop"      # new default rides the alias


def test_quantized_composes_with_zero2_and_bf16():
    """bf16 + ZeRO-2 + compressed_allreduce: the engine's compute-dtype
    cast runs inside the quantized shard_map path, where 'data' is a
    MANUAL axis — the ZeRO cast sharding-constraint must not be emitted
    there (round-5 regression: with_sharding_constraint referencing a
    manual mesh axis is a trace-time error)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "compressed_allreduce": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    losses = []
    for i in range(4):
        bs = random_batches(2, 32, 8, seed=i)
        losses.append(float(e.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
