"""int8 block-quantized gradient allreduce (TPU-native extension;
ZeRO++-style comm compression, runtime/quantized_collectives.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.quantized_collectives import (
    dequantize_blockwise, quantize_blockwise, quantized_allreduce_mean,
    wire_bytes)


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1000).astype(np.float32) * 3)
    q, s, n = quantize_blockwise(x, block=256)
    y = dequantize_blockwise(q, s, n)
    # per-element error <= absmax_of_block / 127 (half-step rounding x2)
    err = np.abs(np.asarray(y) - np.asarray(x))
    bound = np.abs(np.asarray(x)).max() / 127 + 1e-7
    assert err.max() <= bound


def test_allreduce_mean_matches_dense_within_quant_error():
    mesh = build_mesh({"data": 8})
    rng = np.random.RandomState(1)
    g = jnp.asarray(rng.randn(8, 512).astype(np.float32))

    def inner(x):
        return quantized_allreduce_mean(x[0], "data")

    out = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_vma=False))(g)
    dense = np.asarray(g).mean(axis=0)
    np.testing.assert_allclose(np.asarray(out), dense, atol=0.05)


def test_wire_volume():
    qb, db = wire_bytes(1_000_000)
    assert db / qb > 3.5  # ~3.7x less traffic than fp32


def test_engine_trains_and_converges():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    eq, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "compressed_allreduce": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert eq._quant_allreduce
    ed, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    lq, ld = [], []
    for i in range(10):
        b = random_batches(1, 32, 8, seed=i)[0]
        lq.append(float(eq.train_batch(iter([b]))))
        ld.append(float(ed.train_batch(iter([b]))))
    assert lq[-1] < lq[0]                       # converges
    np.testing.assert_allclose(lq, ld, rtol=0.2)  # tracks the dense run


def test_fp16_overflow_survives_quantization():
    """An fp16 overflow (inf grads) must still trip the skip-step
    machinery — quantization alone would launder inf into garbage."""
    from tests.unit.simple_model import init_simple_params, random_batches

    def exploding_loss(params, batch):
        x = batch["x"] * 1e4  # fp16 overflow in the first matmul
        for i in range(len(params)):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"].astype(x.dtype) + layer["b"].astype(x.dtype)
        return jnp.mean(x.astype(jnp.float32) ** 2)

    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=exploding_loss, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "compressed_allreduce": {"enabled": True},
                "fp16": {"enabled": True, "initial_scale_power": 20},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    b = random_batches(1, 32, 8)[0]
    before = jax.tree_util.tree_map(np.asarray, e.state.params)
    e.train_batch(iter([b]))
    assert e.skipped_steps >= 1          # overflow detected -> skipped
    for a, c in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(e.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_quantized_composes_with_zero2_and_accumulation():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "compressed_allreduce": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    losses = []
    for i in range(4):
        bs = random_batches(2, 32, 8, seed=i)
        losses.append(float(e.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_quantized_composes_with_zero2_and_bf16():
    """bf16 + ZeRO-2 + compressed_allreduce: the engine's compute-dtype
    cast runs inside the quantized shard_map path, where 'data' is a
    MANUAL axis — the ZeRO cast sharding-constraint must not be emitted
    there (round-5 regression: with_sharding_constraint referencing a
    manual mesh axis is a trace-time error)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    e, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "compressed_allreduce": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    losses = []
    for i in range(4):
        bs = random_batches(2, 32, 8, seed=i)
        losses.append(float(e.train_batch(iter(bs))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
