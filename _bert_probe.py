import time, numpy as np, jax
import deepspeed_tpu
from deepspeed_tpu.models.bert import BERT_LARGE, bert_mlm_loss_fn, init_bert_params
from jax.sharding import NamedSharding, PartitionSpec

def run(batch, steps=8):
    params = init_bert_params(BERT_LARGE, jax.random.PRNGKey(0))
    loss_fn = bert_mlm_loss_fn(BERT_LARGE, deterministic=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": batch,
                "bf16": {"enabled": True}, "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}}})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, BERT_LARGE.vocab_size, (batch, 128)).astype(np.int32)
    labels = np.where(rng.rand(batch, 128) < 0.15, ids, -100).astype(np.int32)
    shd = NamedSharding(engine.mesh, PartitionSpec())
    b = {"input_ids": jax.device_put(ids, shd), "labels": jax.device_put(labels, shd)}
    loss = engine.train_batch(iter([b])); np.asarray(loss)
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps): loss = engine.train_batch(iter([b]))
        np.asarray(loss)
        w = (time.perf_counter()-t0)/steps
        best = min(best, w) if best else w
    print(f"batch={batch}: {batch/best:.1f} samples/s ({best*1e3:.1f} ms/step)", flush=True)

for bs in (32, 64, 128):
    run(bs)
